"""Priority feedback arbiter (ref: cmd/vGPUmonitor/feedback.go:164-254).

The reference ships this disabled (main.go:26 comments out watchAndFeedback)
— we ship it working: every tick the arbiter decays each region's
``recent_kernel`` activity counter and flips ``utilization_switch`` so that
when any HIGH-priority (priority 0) process was recently active, LOW-priority
regions get their core throttling *tightened* (switch stays 0 = enforce) and
high-priority regions get their throttle suspended (switch 1).  When no
high-priority work is active, everyone's limits enforce normally.

Tiered preemption (docs/scheduler_perf.md §Tiered preemption) extends the
binary switch into a throttle LADDER for best-effort tenants
(``TPU_TASK_PRIORITY >= 2``, injected by the webhook for
``vtpu.io/qos: best-effort`` pods): while a guaranteed-tier tenant
(priority 0/1) is active alongside an active best-effort tenant, the
arbiter walks each best-effort region's switch up one squeeze level per
pass (2 → 3 → 4; the shim's pacing path halves the effective core quota
per level via ``effective_core_limit``), and restores it to 0 the pass
contention clears.  If contention persists past ``VTPU_EVICT_AFTER_S``,
the arbiter marks the best-effort pod with ``vtpu.io/evict-requested`` —
the scheduler's eviction reconciler turns that into a delete and releases
the overlay booking.  Squeeze-first-evict-last: oversubscribed tenants
degrade gracefully before any is killed, and guaranteed tenants never
degrade for long.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional

from vtpu import obs
from vtpu.monitor.pathmonitor import PathMonitor
from vtpu.monitor.shared_region import THROTTLE_LEVEL_MAX, THROTTLE_LEVEL_MIN
from vtpu.obs.events import EventType, emit
from vtpu.utils.envs import env_float as _env_float
from vtpu.utils.types import BEST_EFFORT_PRIORITY, annotations

log = logging.getLogger(__name__)

ACTIVITY_THRESHOLD = 1  # recent_kernel above this = "recently active"
ENV_ACTIVITY_THRESHOLD = "VTPU_FEEDBACK_ACTIVITY_THRESHOLD"
# contention older than this asks for eviction (docs/config.md)
ENV_EVICT_AFTER = "VTPU_EVICT_AFTER_S"
DEFAULT_EVICT_AFTER_S = 60.0

_MON = obs.registry("monitor")
_PASS_HIST = _MON.histogram(
    "vtpu_feedback_pass_seconds",
    "One feedback-arbiter pass: scan + decay/arbitrate + hostpid fill + reap",
)
_FAILURES = _MON.counter(
    "vtpu_feedback_failures_total",
    "Feedback passes that raised (logged and retried next tick)",
)
_THROTTLE_FLIPS = _MON.counter(
    "vtpu_preempt_throttle_transitions_total",
    "utilization_switch transitions written by the arbiter, by target "
    "state (suspend / enforce / squeeze level)",
)
_EVICT_REQS = _MON.counter(
    "vtpu_preempt_evict_requests_total",
    "Best-effort pods marked vtpu.io/evict-requested after contention "
    "outlasted VTPU_EVICT_AFTER_S",
)


def _activity_threshold(explicit: Optional[int] = None) -> int:
    if explicit is not None:
        return explicit
    return int(_env_float(ENV_ACTIVITY_THRESHOLD, ACTIVITY_THRESHOLD))


def _switch_label(value: int) -> str:
    if value == 0:
        return "enforce"
    if value == 1:
        return "suspend"
    return f"squeeze_{value}"


class ContentionArbiter:
    """Stateful side of the feedback pass: per-region squeeze levels,
    contention clocks, and the one-shot eviction requests.

    ``client``/``pods_fn`` are optional — without them the ladder still
    squeezes (it lives in the shared region), but eviction requests are
    only journaled, not annotated (the pod-side patch needs the API)."""

    def __init__(
        self,
        client=None,
        pods_fn: Optional[Callable[[], dict]] = None,
        evict_after_s: Optional[float] = None,
        activity_threshold: Optional[int] = None,
        clock=time.monotonic,
        wallclock=time.time,
    ) -> None:
        self.client = client
        self.pods_fn = pods_fn
        if evict_after_s is None:
            evict_after_s = _env_float(ENV_EVICT_AFTER, DEFAULT_EVICT_AFTER_S)
        self.evict_after_s = evict_after_s
        self.activity_threshold = _activity_threshold(activity_threshold)
        self._clock = clock
        self._wallclock = wallclock
        # dirname → monotonic ts contention FIRST held (uninterrupted)
        self._contention_since: Dict[str, float] = {}
        # pod uid → region dirname, for uids already marked (one patch
        # per contention episode; purged when the region vanishes)
        self._evict_requested: Dict[str, str] = {}

    def _set_switch(self, entry, value: int) -> None:
        """Write the switch only on change, making the transition visible:
        ThrottleChanged journal event + transitions counter — squeeze and
        restore flips show up on /timeline next to the pod's spans."""
        region = entry.region
        cur = region.region.utilization_switch
        if cur == value:
            return
        region.set_utilization_switch(value)
        _THROTTLE_FLIPS.inc(to=_switch_label(value))
        emit(
            EventType.THROTTLE_CHANGED, "monitor",
            pod=entry.pod_uid, ctr=entry.dirname,
            prev=_switch_label(cur), now=_switch_label(value),
            # raw ladder level rides along so outcome records (and any
            # offline join) get the squeeze depth as a number, not just
            # the label (vtpu/obs/outcomes.py)
            level=value,
        )

    def _request_eviction(self, entry) -> None:
        uid = entry.pod_uid
        if uid in self._evict_requested:
            return
        self._evict_requested[uid] = entry.dirname
        reason = f"besteffort_contention_{int(self._wallclock())}"
        patched = False
        if self.client is not None and self.pods_fn is not None:
            try:
                pod = (self.pods_fn() or {}).get(uid)
                if pod is None:
                    # transient list miss (API/informer lag): don't burn
                    # the episode's one-shot on a no-op — retried while
                    # the contention clock stays over the threshold
                    self._evict_requested.pop(uid, None)
                    log.warning(
                        "evict-request: pod %s not in API snapshot yet; "
                        "will retry next pass", uid,
                    )
                    return
                meta = pod.get("metadata", {})
                self.client.patch_pod_annotations(
                    meta.get("namespace", "default"), meta.get("name", ""),
                    {annotations.EVICT_REQUESTED: reason},
                )
                patched = True
            except Exception:  # noqa: BLE001 — retried next pass
                log.exception("evict-request patch for pod %s failed", uid)
                self._evict_requested.pop(uid, None)
                return
        _EVICT_REQS.inc()
        emit(
            EventType.EVICT_REQUESTED, "monitor",
            pod=uid, ctr=entry.dirname, reason=reason, patched=patched,
        )
        log.warning(
            "best-effort pod %s kept guaranteed tier suppressed > %.0fs: "
            "eviction requested (%s)", uid, self.evict_after_s, reason,
        )

    def observe(self, pathmon: PathMonitor) -> None:
        """One arbitration pass (ref Observe + CheckPriority
        feedback.go:164-222, plus the squeeze ladder)."""
        entries = [e for e in pathmon.entries.values() if e.region is not None]
        threshold = self.activity_threshold
        high_active = False          # priority-0 work recently ran
        guaranteed_active = False    # any guaranteed-tier (0/1) work ran
        besteffort_active = False
        activity = {}
        for e in entries:
            act = e.region.decay_recent_kernel()
            procs = e.region.live_procs()
            prio = min((p["priority"] for p in procs), default=1)
            if not procs:
                # no registered tenant: residual decaying activity from an
                # exited process is not work — without this, a dead region
                # (default prio 1) reads as guaranteed-active and squeezes
                # best-effort co-tenants on a node with no guaranteed work
                act = 0.0
            activity[e.dirname] = (act, prio)
            if act > threshold:
                if prio == 0:
                    high_active = True
                if prio <= 1:
                    guaranteed_active = True
                elif prio >= BEST_EFFORT_PRIORITY:
                    besteffort_active = True
        # contention: a guaranteed tenant is burning cycles while a
        # best-effort co-tenant is too — squeeze the opportunistic tier
        contention = guaranteed_active and besteffort_active
        now = self._clock()
        live_dirs = set()
        for e in entries:
            act, prio = activity[e.dirname]
            live_dirs.add(e.dirname)
            if prio >= BEST_EFFORT_PRIORITY:
                # only a best-effort tenant that is ITSELF burning cycles
                # is part of the contention — an idle co-tenant keeps its
                # quota and never accrues an eviction clock just because
                # a sibling suppressed the guaranteed tier
                if contention and act > threshold:
                    since = self._contention_since.setdefault(e.dirname, now)
                    cur = e.region.region.utilization_switch
                    nxt = (
                        THROTTLE_LEVEL_MIN
                        if cur < THROTTLE_LEVEL_MIN
                        else min(THROTTLE_LEVEL_MAX, cur + 1)
                    )
                    self._set_switch(e, nxt)
                    if now - since >= self.evict_after_s:
                        self._request_eviction(e)
                else:
                    self._contention_since.pop(e.dirname, None)
                    # clear the pod-level one-shot only if THIS region
                    # requested it — an idle sibling region of the same
                    # pod must not re-arm the request every pass
                    if self._evict_requested.get(e.pod_uid) == e.dirname:
                        self._evict_requested.pop(e.pod_uid, None)
                    self._set_switch(e, 0)
            elif prio == 0 and high_active:
                # high-priority task running: it gets unthrottled
                self._set_switch(e, 1)
            else:
                self._set_switch(e, 0)
        # forget state for vanished regions (evicted/retired tenants) —
        # including their one-shot eviction marks, or the uid set grows
        # for the life of the daemon under best-effort churn
        for gone in [d for d in self._contention_since if d not in live_dirs]:
            self._contention_since.pop(gone, None)
        for uid in [
            u for u, d in self._evict_requested.items() if d not in live_dirs
        ]:
            self._evict_requested.pop(uid, None)


def observe_once(
    pathmon: PathMonitor, arbiter: Optional[ContentionArbiter] = None
) -> None:
    """One arbitration pass.  Stateless callers (tests, one-shot tools)
    get a transient arbiter: the binary suspend behaviour is identical;
    squeeze escalation/eviction clocks simply restart each call."""
    (arbiter or ContentionArbiter()).observe(pathmon)


class FeedbackLoop:
    """Lifecycle-safe wrapper around the arbiter thread: ``start()`` is
    idempotent while the thread is alive (a double start must not spawn a
    second arbiter racing the first over utilization_switch), the thread
    handle is retained, and ``stop()`` joins with a timeout."""

    def __init__(
        self,
        pathmon: PathMonitor,
        interval_s: float = 5.0,
        client=None,
        pods_fn: Optional[Callable[[], dict]] = None,
        evict_after_s: Optional[float] = None,
        activity_threshold: Optional[int] = None,
    ) -> None:
        self.pathmon = pathmon
        self.interval_s = interval_s
        self.arbiter = ContentionArbiter(
            client=client,
            pods_fn=pods_fn,
            evict_after_s=evict_after_s,
            activity_threshold=activity_threshold,
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _pass_once(self) -> None:
        from vtpu.monitor.hostpid import fill_hostpids, reap_dead_by_hostpid

        t0 = time.perf_counter()
        try:
            self.pathmon.scan()
            self.arbiter.observe(self.pathmon)
            # resolve container→host pids for new slots each tick
            # (ref setHostPid runs inside the feedback loop too),
            # then free slots whose host process died — a crashed
            # tenant must not pin its quota bytes
            fill_hostpids(self.pathmon)
            reaped = reap_dead_by_hostpid(self.pathmon)
            if reaped:
                log.info("reaped %d dead tenant slot(s)", reaped)
        except Exception:  # noqa: BLE001
            _FAILURES.inc()
            log.exception("feedback pass failed")
        finally:
            _PASS_HIST.observe(time.perf_counter() - t0)

    def start(self) -> bool:
        """Start the loop; returns False (no-op) when already running."""
        if self._thread is not None and self._thread.is_alive():
            log.warning("feedback loop already running; start() ignored")
            return False
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(self.interval_s):
                self._pass_once()

        self._thread = threading.Thread(
            target=loop, name="vtpu-feedback", daemon=True
        )
        self._thread.start()
        return True

    def stop(self, timeout: Optional[float] = 5.0) -> None:
        """Signal the loop and join the thread (bounded by ``timeout``)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
