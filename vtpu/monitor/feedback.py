"""Priority feedback arbiter (ref: cmd/vGPUmonitor/feedback.go:164-254).

The reference ships this disabled (main.go:26 comments out watchAndFeedback)
— we ship it working: every tick the arbiter decays each region's
``recent_kernel`` activity counter and flips ``utilization_switch`` so that
when any HIGH-priority (priority 0) process was recently active, LOW-priority
regions get their core throttling *tightened* (switch stays 0 = enforce) and
high-priority regions get their throttle suspended (switch 1).  When no
high-priority work is active, everyone's limits enforce normally.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Iterable, Optional

from vtpu import obs
from vtpu.monitor.pathmonitor import PathMonitor

log = logging.getLogger(__name__)

ACTIVITY_THRESHOLD = 1  # recent_kernel above this = "recently active"

_MON = obs.registry("monitor")
_PASS_HIST = _MON.histogram(
    "vtpu_feedback_pass_seconds",
    "One feedback-arbiter pass: scan + decay/arbitrate + hostpid fill + reap",
)
_FAILURES = _MON.counter(
    "vtpu_feedback_failures_total",
    "Feedback passes that raised (logged and retried next tick)",
)


def observe_once(pathmon: PathMonitor) -> None:
    """One arbitration pass (ref Observe + CheckPriority feedback.go:164-222)."""
    entries = [e for e in pathmon.entries.values() if e.region is not None]
    # classify regions by the min priority of their live procs (0 = high)
    high_active = False
    activity = {}
    for e in entries:
        act = e.region.decay_recent_kernel()
        procs = e.region.live_procs()
        prio = min((p["priority"] for p in procs), default=1)
        activity[e.dirname] = (act, prio)
        if prio == 0 and act > ACTIVITY_THRESHOLD:
            high_active = True
    for e in entries:
        act, prio = activity[e.dirname]
        if prio == 0 and high_active:
            # high-priority task running: it gets unthrottled
            e.region.set_utilization_switch(1)
        else:
            e.region.set_utilization_switch(0)


class FeedbackLoop:
    """Lifecycle-safe wrapper around the arbiter thread: ``start()`` is
    idempotent while the thread is alive (a double start must not spawn a
    second arbiter racing the first over utilization_switch), the thread
    handle is retained, and ``stop()`` joins with a timeout."""

    def __init__(self, pathmon: PathMonitor, interval_s: float = 5.0) -> None:
        self.pathmon = pathmon
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _pass_once(self) -> None:
        from vtpu.monitor.hostpid import fill_hostpids, reap_dead_by_hostpid

        t0 = time.perf_counter()
        try:
            self.pathmon.scan()
            observe_once(self.pathmon)
            # resolve container→host pids for new slots each tick
            # (ref setHostPid runs inside the feedback loop too),
            # then free slots whose host process died — a crashed
            # tenant must not pin its quota bytes
            fill_hostpids(self.pathmon)
            reaped = reap_dead_by_hostpid(self.pathmon)
            if reaped:
                log.info("reaped %d dead tenant slot(s)", reaped)
        except Exception:  # noqa: BLE001
            _FAILURES.inc()
            log.exception("feedback pass failed")
        finally:
            _PASS_HIST.observe(time.perf_counter() - t0)

    def start(self) -> bool:
        """Start the loop; returns False (no-op) when already running."""
        if self._thread is not None and self._thread.is_alive():
            log.warning("feedback loop already running; start() ignored")
            return False
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(self.interval_s):
                self._pass_once()

        self._thread = threading.Thread(
            target=loop, name="vtpu-feedback", daemon=True
        )
        self._thread.start()
        return True

    def stop(self, timeout: Optional[float] = 5.0) -> None:
        """Signal the loop and join the thread (bounded by ``timeout``)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
