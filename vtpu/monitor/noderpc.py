"""Node info gRPC service (ref: cmd/vGPUmonitor/noderpc + pathmonitor.go:116-140).

The reference registers this server with unimplemented methods; vtpu serves
real data from the shared regions.
"""

from __future__ import annotations

import logging
from concurrent import futures
from typing import Optional, Tuple

import grpc

from vtpu.monitor import noderpc_pb2 as pb
from vtpu.monitor.pathmonitor import PathMonitor

log = logging.getLogger(__name__)

SERVICE = "vtpunoderpc.NodeVtpuInfo"


def _container_usage(entry) -> pb.ContainerUsage:
    cu = pb.ContainerUsage(ctr_id=entry.dirname, pod_uid=entry.pod_uid)
    r = entry.region
    if r is None:
        return cu
    uuids = r.device_uuids()
    limits = r.limits()
    cores = r.core_limits()
    usage = r.usage()
    for i, uuid in enumerate(uuids):
        cu.devices.append(
            pb.DeviceUsage(
                uuid=uuid,
                limit_bytes=limits[i],
                used_bytes=usage[i]["total"],
                buffer_bytes=usage[i]["buffer"],
                program_bytes=usage[i]["program"],
                swap_bytes=usage[i].get("swap", 0),
                core_limit=cores[i],
                # utilization profiling (region v4): monotonic counters
                # summed across live procs + the HBM high-watermark
                busy_ns=usage[i].get("busy_ns", 0),
                launches=usage[i].get("launches", 0),
                hbm_peak_bytes=usage[i].get("hbm_peak", 0),
            )
        )
    procs = r.live_procs()
    cu.proc_num = len(procs)
    for p in procs:
        cu.procs.append(
            pb.ProcInfo(
                pid=p["pid"],
                hostpid=p.get("hostpid", 0),
                exec_calls=p.get("exec_calls", 0),
                exec_shim_ns=p.get("exec_shim_ns", 0),
                busy_ns=p.get("busy_ns", 0),
                launches=p.get("launches", 0),
            )
        )
    return cu


class NodeVtpuServicer:
    def __init__(self, pathmon: PathMonitor) -> None:
        self.pathmon = pathmon

    def GetNodeVtpu(self, request, context):  # noqa: N802
        reply = pb.NodeVtpuReply()
        entries = self.pathmon.scan()
        for name, entry in sorted(entries.items()):
            if request.ctr_id and name != request.ctr_id:
                continue
            reply.containers.append(_container_usage(entry))
        return reply


def serve_noderpc(
    pathmon: PathMonitor, bind: str = "0.0.0.0:9395"
) -> Tuple[grpc.Server, int]:
    """Returns (server, bound_port) — port matters when binding :0."""
    servicer = NodeVtpuServicer(pathmon)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    handlers = {
        "GetNodeVtpu": grpc.unary_unary_rpc_method_handler(
            servicer.GetNodeVtpu,
            request_deserializer=pb.GetNodeVtpuRequest.FromString,
            response_serializer=pb.NodeVtpuReply.SerializeToString,
        )
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),)
    )
    port = server.add_insecure_port(bind)
    server.start()
    return server, port


class NodeVtpuStub:
    def __init__(self, channel: grpc.Channel) -> None:
        self.GetNodeVtpu = channel.unary_unary(
            f"/{SERVICE}/GetNodeVtpu",
            request_serializer=pb.GetNodeVtpuRequest.SerializeToString,
            response_deserializer=pb.NodeVtpuReply.FromString,
        )
