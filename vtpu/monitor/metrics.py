"""Node-monitor Prometheus exporter (ref: cmd/vGPUmonitor/metrics.go:140-246).

Serves :9394/metrics — host chip stats from the device provider plus
per-container real usage read from the shared regions.  This is where the
BASELINE "HBM-quota violations" metric comes from: usage > limit in any
region is a violation.

Exposition built on the shared vtpu.obs renderer; the legacy families are
byte-identical to the pre-obs output (tests/golden/monitor_metrics.txt)
with the obs registry's families appended, and the HTTP server also
mounts the shared /spans + /timeline debug surface.
"""

from __future__ import annotations

import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from vtpu import obs
from vtpu.obs import render_family
from vtpu.monitor.pathmonitor import PathMonitor

log = logging.getLogger(__name__)

_MB = 1024 * 1024


def render_node_metrics(
    pathmon: PathMonitor,
    provider=None,
    pods_by_uid: Optional[Dict[str, dict]] = None,
    include_obs: bool = True,
) -> str:
    """``include_obs=False`` stops after the legacy families (golden
    regeneration must not bake in timing-dependent histogram counts)."""
    lines: List[str] = []

    def gauge(name: str, help_: str, samples: List[Tuple[dict, float]],
              typ: str = "gauge") -> None:
        render_family(lines, name, help_, typ, samples)

    # host-level chip inventory (ref HostGPUMemoryUsage/HostCoreUtilization)
    host_mem = []
    if provider is not None:
        for chip in provider.enumerate():
            host_mem.append(
                ({"deviceuuid": chip.uuid, "devicetype": chip.model},
                 chip.hbm_mb * _MB)
            )
    gauge("vtpu_host_device_memory_bytes", "Physical HBM per local chip", host_mem)

    usage_s, limit_s, breakdown_s, violation_s = [], [], [], []
    exec_calls_s, exec_shim_s = [], []
    entries = pathmon.scan(
        set(pods_by_uid) if pods_by_uid is not None else None
    )
    for name, entry in sorted(entries.items()):
        if entry.region is None:
            continue
        pod = (pods_by_uid or {}).get(entry.pod_uid, {})
        podname = pod.get("metadata", {}).get("name", "")
        podns = pod.get("metadata", {}).get("namespace", "")
        uuids = entry.region.device_uuids()
        limits = entry.region.limits()
        usage = entry.region.usage()
        for i, uuid in enumerate(uuids):
            labels = {
                "ctr": name,
                "podname": podname,
                "podnamespace": podns,
                "vdeviceid": i,
                "deviceuuid": uuid,
            }
            usage_s.append((labels, usage[i]["total"]))
            limit_s.append((labels, limits[i]))
            for kind in ("buffer", "program", "swap"):
                breakdown_s.append(
                    (dict(labels, kind=kind), usage[i].get(kind, 0))
                )
            violation_s.append(
                (labels, 1 if limits[i] and usage[i]["total"] > limits[i] else 0)
            )
        for proc in entry.region.live_procs():
            plabels = {
                "ctr": name, "podname": podname, "podnamespace": podns,
                "pid": proc["pid"],
            }
            exec_calls_s.append((plabels, proc.get("exec_calls", 0)))
            exec_shim_s.append(
                (plabels, proc.get("exec_shim_ns", 0) / 1e9)
            )
    gauge(
        "vtpu_container_device_memory_usage_bytes",
        "Real per-container per-vdevice HBM usage (ref vGPU_device_memory_usage_in_bytes)",
        usage_s,
    )
    gauge(
        "vtpu_container_device_memory_limit_bytes",
        "Per-container per-vdevice HBM quota (ref vGPU_device_memory_limit_in_bytes)",
        limit_s,
    )
    gauge(
        "vtpu_container_device_memory_breakdown_bytes",
        "Usage split by kind (ref Device_memory_desc_of_container)",
        breakdown_s,
    )
    gauge(
        "vtpu_container_quota_violation",
        "1 when a container exceeds its HBM quota (BASELINE acceptance metric)",
        violation_s,
    )
    # interposer telemetry (beyond the reference): quantifies what the
    # enforcement layer itself costs each tenant, straight from the shim
    gauge(
        "vtpu_proc_executes_total",
        "Executes dispatched through the shim per tenant process",
        exec_calls_s,
        typ="counter",  # _total + monotonic: rate()/increase() need this
    )
    gauge(
        "vtpu_proc_shim_overhead_seconds_total",
        "Wrapper-added time (excl. pacing) per tenant process",
        exec_shim_s,
        typ="counter",
    )
    # obs-registry families (in-process shim histograms when tenants run
    # embedded, monitor-side instruments) — appended AFTER the legacy
    # families so the pre-obs exposition stays a byte-exact prefix
    legacy = "\n".join(lines) + "\n"
    if not include_obs:
        return legacy
    # "obs" carries the cross-component families (event counts, readiness
    # breakdown) — one registry so the monitor+shim concatenation can
    # never repeat a family name
    return (legacy
            + obs.registry("monitor").render()
            + obs.registry("shim").render()
            + obs.registry("obs").render())


def serve_metrics(
    pathmon: PathMonitor,
    provider=None,
    pods_fn=None,
    bind: str = "0.0.0.0:9394",
    sampler=None,
) -> Tuple[ThreadingHTTPServer, threading.Thread]:
    """ref metrics.go — :9394/metrics endpoint.  With a
    ``UtilizationSampler`` attached the server also serves
    ``GET /utilization?pod=&window=`` (JSON duty-cycle time series) and
    merges the sampler's counter events into ``/trace.json`` so duty
    cycle renders beside the span feed."""

    class Handler(BaseHTTPRequestHandler):
        def _send(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802
            route = self.path.split("?", 1)[0]
            if route == "/utilization":
                from vtpu.obs.http import split_query

                if sampler is None:
                    self._send(404, b'{"error": "no sampler attached"}',
                               "application/json")
                    return
                _, params = split_query(self.path)
                try:
                    body = sampler.utilization_body(params)
                except Exception as e:  # noqa: BLE001
                    log.exception("utilization render failed")
                    self._send(500, str(e).encode(), "text/plain")
                    return
                self._send(200, body, "application/json")
                return
            if route == "/trace.json" and sampler is not None:
                try:
                    body = sampler.merged_chrome().encode()
                except Exception as e:  # noqa: BLE001
                    log.exception("trace merge failed")
                    self._send(500, str(e).encode(), "text/plain")
                    return
                self._send(200, body, "application/json")
                return
            if route in ("/spans", "/timeline", "/trace.json", "/events",
                         "/outcomes", "/readyz"):
                # shared debug surface (vtpu/obs/http.py): span feed,
                # event journal, decision→outcome join records, and the
                # deep-readiness probe
                from vtpu.obs.http import handle_debug_get

                if not handle_debug_get(self, self._send,
                                        ready_components=("monitor",)):
                    self._send(404, b"not found", "text/plain")
                return
            if self.path == "/healthz":
                body = b"ok"
                ctype = "text/plain"
            elif self.path == "/metrics":
                try:
                    pods = pods_fn() if pods_fn else None
                    body = render_node_metrics(pathmon, provider, pods).encode()
                    ctype = "text/plain; version=0.0.4"
                except Exception as e:  # noqa: BLE001
                    log.exception("metrics render failed")
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(str(e).encode())
                    return
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # quiet
            log.debug("monitor http: " + fmt, *args)

    host, _, port = bind.rpartition(":")
    srv = ThreadingHTTPServer((host or "0.0.0.0", int(port)), Handler)
    t = threading.Thread(target=srv.serve_forever, name="vtpu-monitor-http", daemon=True)
    t.start()
    return srv, t
