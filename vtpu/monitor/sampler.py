"""Per-pod utilization profiling: the duty-cycle sampler.

The reference's vGPUmonitor exports instantaneous byte totals only; its
per-pod *usage* story (metrics.go Collect + the decayed recentKernel
counter) never answers "what fraction of its core quota did pod X actually
use?".  This sampler closes that gap: every tick it diffs the region-v4
monotonic counters (cumulative device-busy ns + kernel-launch count,
written by the shim's pacing path) into per-pod per-device **duty-cycle
ratios**, tracks the HBM high-watermark, retains a bounded ring-buffer
time series per (container, device), and publishes:

- Prometheus families through the shared ``vtpu/obs`` monitor registry
  (``vtpu_pod_duty_cycle_ratio``, ``vtpu_pod_hbm_high_watermark_bytes``,
  ``vtpu_pod_kernel_launches_total``, ``vtpu_pod_quota_headroom_ratio``);
- ``GET /utilization?pod=&window=`` JSON time series (mounted by
  vtpu/monitor/metrics.py);
- Chrome trace counter events merged into ``/trace.json`` so duty cycle
  renders as a track beside the pod-lifecycle spans;
- a rate-limited, delta-gated ``vtpu.io/node-utilization`` node
  annotation summarizing per-device duty — the write-back the scheduler's
  UsageCache ingests (the feedback loop the reference sketched in
  feedback.go but shipped disabled).

Clocks are injectable (``clock`` = monotonic seconds for diffing,
``wallclock`` = epoch seconds for series/trace timestamps) so the
duty-cycle oracle tests run on a fake clock with zero sleeps.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from typing import Deque, Dict, List, Optional, Tuple

from vtpu import obs
from vtpu.obs import outcomes
from vtpu.monitor.pathmonitor import PathMonitor
from vtpu.utils import trace
from vtpu.analysis.witness import make_lock
from vtpu.utils.types import annotations

log = logging.getLogger(__name__)

_MON = obs.registry("monitor")
_DUTY = _MON.gauge(
    "vtpu_pod_duty_cycle_ratio",
    "Measured per-pod per-device duty cycle over the last sample window "
    "(Δbusy_ns / Δwall; 1.0 = the device ran this pod's work the whole "
    "window)",
)
_HBM_PEAK = _MON.gauge(
    "vtpu_pod_hbm_high_watermark_bytes",
    "Per-pod per-device HBM high-watermark (ratchets on allocation, "
    "summed across the pod's processes)",
)
_HEADROOM = _MON.gauge(
    "vtpu_pod_quota_headroom_ratio",
    "Unused fraction of the pod's core quota ((quota - duty) / quota; "
    "negative = overrun, e.g. priority suspend lifted the throttle)",
)
_LAUNCHES = _MON.counter(
    "vtpu_pod_kernel_launches_total",
    "Kernel/execute launches per pod per device (diffed from the region's "
    "monotonic counter)",
)
_SAMPLES = _MON.counter(
    "vtpu_util_samples_total",
    "Utilization sampler passes completed",
)
_WRITEBACK = _MON.counter(
    "vtpu_util_writeback_total",
    "Node-utilization annotation write-back attempts by result "
    "(written / skipped_interval / skipped_delta / error)",
)

# env knobs (docs/config.md — monitor envs)
DEFAULT_INTERVAL_S = 5.0
DEFAULT_SERIES_CAP = 720          # 1 h of history at the 5 s default
DEFAULT_WRITEBACK_MIN_INTERVAL_S = 30.0
DEFAULT_WRITEBACK_MIN_DELTA = 0.05
# delta-gate ceiling: past this age the annotation is rewritten even
# with unchanged duties, so its ts keeps advancing on idle nodes — the
# scheduler-side auditor reads the ts as a heartbeat (stale at 120 s)
DEFAULT_WRITEBACK_MAX_AGE_S = 60.0


from vtpu.utils.envs import env_float as _env_float  # noqa: E402


class UtilizationSampler:
    """Continuous duty-cycle profiler over a PathMonitor's regions."""

    def __init__(
        self,
        pathmon: PathMonitor,
        interval_s: Optional[float] = None,
        series_cap: Optional[int] = None,
        pods_fn=None,
        clock=time.monotonic,
        wallclock=time.time,
        writeback_client=None,
        node_name: str = "",
        writeback_min_interval_s: Optional[float] = None,
        writeback_min_delta: Optional[float] = None,
    ) -> None:
        self.pathmon = pathmon
        self.interval_s = (
            interval_s
            if interval_s is not None
            else _env_float("VTPU_UTIL_SAMPLE_INTERVAL", DEFAULT_INTERVAL_S)
        )
        cap = (
            series_cap
            if series_cap is not None
            else int(_env_float("VTPU_UTIL_SERIES_CAP", DEFAULT_SERIES_CAP))
        )
        self.series_cap = max(1, cap)
        self._pods_fn = pods_fn
        self._clock = clock
        self._wallclock = wallclock
        # node write-back (gating state lives here, not in the loop, so
        # tests can drive writeback_once directly)
        self.writeback_client = writeback_client
        self.node_name = node_name or os.environ.get("NODE_NAME", "")
        self.writeback_min_interval_s = (
            writeback_min_interval_s
            if writeback_min_interval_s is not None
            else _env_float(
                "VTPU_UTIL_WRITEBACK_MIN_INTERVAL_S",
                DEFAULT_WRITEBACK_MIN_INTERVAL_S,
            )
        )
        self.writeback_min_delta = (
            writeback_min_delta
            if writeback_min_delta is not None
            else _env_float(
                "VTPU_UTIL_WRITEBACK_MIN_DELTA", DEFAULT_WRITEBACK_MIN_DELTA
            )
        )
        self.writeback_max_age_s = _env_float(
            "VTPU_UTIL_WRITEBACK_MAX_AGE_S", DEFAULT_WRITEBACK_MAX_AGE_S
        )
        self._lock = make_lock("monitor.sampler")
        # sampler health, read by the monitor's /readyz "util_sampler"
        # check (monotonic clock so fake-clock tests stay deterministic)
        self._last_sample_t: Optional[float] = None
        self._started_t: Optional[float] = None
        # (ctr dirname, dev index) → (mono_t, busy_ns, launches)
        self._prev: Dict[Tuple[str, int], Tuple[float, int, int]] = {}
        # ctr dirname → dev index → ring of sample points
        self._series: Dict[str, Dict[int, Deque[dict]]] = {}
        # ctr dirname → (pod_uid, podname, podns, [uuids])
        self._meta: Dict[str, Tuple[str, str, str, List[str]]] = {}
        self._node_summary: Dict[str, dict] = {}  # uuid → {"duty", "hbm_peak"}
        # pod_uid → {"hbm_peak": bytes}: rides the write-back so the
        # scheduler's auditor can spot orphaned regions cluster-wide
        self._pods_summary: Dict[str, dict] = {}
        self._last_writeback_t: Optional[float] = None
        self._last_writeback_duty: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- sampling ------------------------------------------------------
    def sample_once(self, scan: bool = True) -> Dict[str, dict]:
        """One sampler pass.  Returns the fresh per-device node summary
        (uuid → duty/hbm_peak) for callers that chain a write-back."""
        now = self._clock()
        wall = self._wallclock()
        entries = self.pathmon.scan() if scan else self.pathmon.entries
        pods = {}
        if self._pods_fn is not None:
            try:
                pods = self._pods_fn() or {}
            except Exception:  # noqa: BLE001 — sampling works without pods
                log.debug("pods_fn failed; sampling without pod names",
                          exc_info=True)
        live: set = set()
        node_duty: Dict[str, float] = {}
        node_peak: Dict[str, int] = {}
        pods_peak: Dict[str, int] = {}
        with self._lock:
            for name, entry in sorted(entries.items()):
                region = entry.region
                if region is None:
                    continue
                try:
                    uuids = region.device_uuids()
                    cores = region.core_limits()
                    usage = region.usage()
                except (OSError, ValueError):
                    continue  # region vanished mid-pass
                pod = pods.get(entry.pod_uid, {})
                podname = pod.get("metadata", {}).get("name", "")
                podns = pod.get("metadata", {}).get("namespace", "")
                prev_meta = self._meta.get(name)
                if not pod and prev_meta is not None:
                    # sticky labels: a transient pods_fn failure (or the
                    # pod vanishing inside the GC grace) must not flip
                    # podname→"" and strand the old-label gauge series
                    podname, podns = prev_meta[1], prev_meta[2]
                elif prev_meta is not None and (
                    (prev_meta[1], prev_meta[2]) != (podname, podns)
                ):
                    # labels really changed: drop the old series so they
                    # do not export their last value forever
                    for old_uuid in prev_meta[3]:
                        old = {
                            "ctr": name, "podname": prev_meta[1],
                            "podnamespace": prev_meta[2],
                            "deviceuuid": old_uuid,
                        }
                        _DUTY.remove(**old)
                        _HBM_PEAK.remove(**old)
                        _HEADROOM.remove(**old)
                self._meta[name] = (entry.pod_uid, podname, podns, uuids)
                for i, u in enumerate(usage):
                    if i >= len(uuids):
                        break
                    key = (name, i)
                    live.add(key)
                    prev = self._prev.get(key)
                    self._prev[key] = (now, u["busy_ns"], u["launches"])
                    uuid = uuids[i]
                    node_peak[uuid] = node_peak.get(uuid, 0) + u["hbm_peak"]
                    pods_peak[entry.pod_uid] = (
                        pods_peak.get(entry.pod_uid, 0) + u["hbm_peak"]
                    )
                    if prev is None:
                        continue
                    dt = now - prev[0]
                    dbusy = u["busy_ns"] - prev[1]
                    dlaunch = u["launches"] - prev[2]
                    if dt <= 0 or dbusy < 0 or dlaunch < 0:
                        # counter went backwards: tenant restarted between
                        # samples — re-baseline instead of reporting noise
                        continue
                    duty = dbusy / 1e9 / dt
                    core = cores[i] if i < len(cores) else 0
                    quota = core / 100.0 if 0 < core < 100 else 1.0
                    headroom = (quota - duty) / quota
                    labels = {
                        "ctr": name, "podname": podname,
                        "podnamespace": podns, "deviceuuid": uuid,
                    }
                    _DUTY.set(duty, **labels)
                    _HBM_PEAK.set(u["hbm_peak"], **labels)
                    _HEADROOM.set(headroom, **labels)
                    if dlaunch:
                        _LAUNCHES.inc(dlaunch, **labels)
                    ring = self._series.setdefault(name, {}).setdefault(
                        i, collections.deque(maxlen=self.series_cap)
                    )
                    ring.append({
                        "t": wall,
                        "duty": duty,
                        "headroom": headroom,
                        "hbm_peak": u["hbm_peak"],
                        "launches": dlaunch,
                        "busy_ns": u["busy_ns"],
                    })
                    node_duty[uuid] = node_duty.get(uuid, 0.0) + duty
            self._prune_locked(live)
            self._node_summary = {
                uuid: {
                    "duty": round(node_duty.get(uuid, 0.0), 4),
                    "hbm_peak": node_peak.get(uuid, 0),
                }
                for uuid in set(node_duty) | set(node_peak)
            }
            self._pods_summary = {
                uid: {"hbm_peak": peak} for uid, peak in sorted(pods_peak.items())
            }
            summary = dict(self._node_summary)
            pods_out = dict(self._pods_summary)
            self._last_sample_t = now
        _SAMPLES.inc()
        # outcome plane (monitor-side): the same payload shape the
        # write-back annotation carries, joined locally so a co-located
        # joiner sees duty without the apiserver round-trip
        if outcomes.joiner() is not None:
            outcomes.observe_utilization(
                self.node_name or "",
                {"v": 1, "ts": wall, "devices": summary, "pods": pods_out},
            )
        return summary

    def _prune_locked(self, live: set) -> None:
        """Forget state (and exported gauge series) for vanished
        containers — a dead pod must not export its last duty forever."""
        for key in [k for k in self._prev if k not in live]:
            name, i = key
            self._prev.pop(key, None)
            devs = self._series.get(name)
            if devs is not None:
                devs.pop(i, None)
                if not devs:
                    self._series.pop(name, None)
            meta = self._meta.get(name)
            if meta is not None and i < len(meta[3]):
                labels = {
                    "ctr": name, "podname": meta[1],
                    "podnamespace": meta[2], "deviceuuid": meta[3][i],
                }
                _DUTY.remove(**labels)
                _HBM_PEAK.remove(**labels)
                _HEADROOM.remove(**labels)
            if not any(k[0] == name for k in self._prev):
                self._meta.pop(name, None)

    # -- query surface (GET /utilization) ------------------------------
    def series(
        self, pod: Optional[str] = None, window_s: Optional[float] = None
    ) -> dict:
        """Time-series view: ``pod`` matches the pod UID or the container
        dirname; ``window_s`` keeps only points newer than now-window."""
        cutoff = (
            self._wallclock() - window_s if window_s and window_s > 0 else None
        )
        out: Dict[str, dict] = {}
        with self._lock:
            for name, devs in self._series.items():
                meta = self._meta.get(name, ("", "", "", []))
                pod_uid = meta[0] or name.rsplit("_", 1)[0]
                if pod and pod not in (pod_uid, name):
                    continue
                uuids = meta[3]
                per_dev = {}
                for i, ring in sorted(devs.items()):
                    points = [
                        p for p in ring
                        if cutoff is None or p["t"] >= cutoff
                    ]
                    if points:
                        uuid = uuids[i] if i < len(uuids) else str(i)
                        per_dev[uuid] = points
                if per_dev:
                    out[name] = {
                        "pod_uid": pod_uid,
                        "podname": meta[1],
                        "podnamespace": meta[2],
                        "devices": per_dev,
                    }
        return {"containers": out, "count": len(out)}

    def utilization_body(self, params: dict) -> bytes:
        """JSON body for GET /utilization?pod=&window= (window seconds)."""
        try:
            window = float(params["window"]) if params.get("window") else None
        except ValueError:
            window = None
        return json.dumps(
            self.series(pod=params.get("pod") or None, window_s=window),
            default=str,
        ).encode()

    # -- Chrome trace merge (/trace.json) ------------------------------
    def chrome_events(self) -> List[dict]:
        """Counter events (ph="C") so duty cycle renders as a per-device
        track beside the span feed in chrome://tracing / Perfetto."""
        events: List[dict] = []
        with self._lock:
            for name, devs in self._series.items():
                meta = self._meta.get(name, ("", "", "", []))
                uuids = meta[3]
                for i, ring in sorted(devs.items()):
                    uuid = uuids[i] if i < len(uuids) else str(i)
                    track = f"duty {name}/{uuid}"
                    for p in ring:
                        events.append({
                            "name": track,
                            "ph": "C",
                            "ts": round(p["t"] * 1e6, 3),
                            "pid": os.getpid(),
                            "cat": "vtpu",
                            "args": {"duty": round(p["duty"], 4)},
                        })
        return events

    def merged_chrome(self) -> str:
        """trace.export_chrome() with this sampler's counter events and
        the journal's instant marks appended — the /trace.json the
        monitor serves."""
        from vtpu.obs import events as events_mod

        doc = json.loads(trace.export_chrome())
        doc["traceEvents"].extend(self.chrome_events())
        doc["traceEvents"].extend(events_mod.journal().chrome_events())
        return json.dumps(doc, default=str)

    # -- node write-back ------------------------------------------------
    def writeback_once(self, summary: Optional[Dict[str, dict]] = None) -> str:
        """Patch the ``vtpu.io/node-utilization`` annotation, gated on a
        minimum interval AND a minimum per-device duty delta (both also
        bypassed when the device set changes).  Returns the outcome
        ("written" / "skipped_interval" / "skipped_delta" / "error" /
        "disabled") — also counted on vtpu_util_writeback_total."""
        if self.writeback_client is None or not self.node_name:
            return "disabled"
        if summary is None:
            with self._lock:
                summary = dict(self._node_summary)
        now = self._clock()
        duties = {u: d["duty"] for u, d in summary.items()}
        if self._last_writeback_t is not None:
            age = now - self._last_writeback_t
            if age < self.writeback_min_interval_s:
                _WRITEBACK.inc(result="skipped_interval")
                return "skipped_interval"
            # the delta gate only applies below the max-age ceiling: on
            # an idle node the annotation's ts must still advance, or
            # the auditor reads a healthy node as stale_heartbeat (and a
            # GC'd region would sit in the stale "pods" map forever)
            if (
                age < self.writeback_max_age_s
                and set(duties) == set(self._last_writeback_duty)
            ):
                delta = max(
                    (abs(duties[u] - self._last_writeback_duty[u])
                     for u in duties),
                    default=0.0,
                )
                if delta < self.writeback_min_delta:
                    _WRITEBACK.inc(result="skipped_delta")
                    return "skipped_delta"
        with self._lock:
            pods = dict(self._pods_summary)
        value = json.dumps(
            # "pods" (per-pod region HBM peaks) feeds the scheduler-side
            # reconciliation auditor's orphaned-region check; readers of
            # v1 ignore unknown keys, so the version stays 1
            {"v": 1, "ts": int(self._wallclock()), "devices": summary,
             "pods": pods},
            sort_keys=True,
        )
        try:
            self.writeback_client.patch_node_annotations(
                self.node_name, {annotations.NODE_UTILIZATION: value}
            )
        except Exception:  # noqa: BLE001 — telemetry must not kill the loop
            log.exception("node-utilization write-back failed")
            _WRITEBACK.inc(result="error")
            return "error"
        self._last_writeback_t = now
        self._last_writeback_duty = duties
        _WRITEBACK.inc(result="written")
        return "written"

    # -- readiness ------------------------------------------------------
    def sampler_status(self) -> tuple:
        """(ok, detail) for the monitor's ``util_sampler`` /readyz
        check: the loop thread must be alive and a sample must have
        landed within ~3 intervals (startup gets the same grace)."""
        t = self._thread
        if t is None or not t.is_alive():
            if self._stop.is_set():
                return False, "sampler stopped"
            return False, "sampler thread dead"
        grace = max(3 * self.interval_s, 1.0)
        with self._lock:
            last = self._last_sample_t
        if last is None:
            started = self._started_t
            if started is not None and self._clock() - started > grace:
                return False, "no sample since start"
            return True, "waiting for first sample"
        age = self._clock() - last
        if age > grace:
            return False, f"last sample {age:.0f}s ago"
        return True, f"last sample {age:.0f}s ago"

    # -- lifecycle ------------------------------------------------------
    def start(self) -> bool:
        """Start the sampling loop; a second call while the thread is
        alive is a no-op (returns False).  Registers the monitor's
        ``util_sampler`` readiness check."""
        if self._thread is not None and self._thread.is_alive():
            return False
        self._stop.clear()
        self._started_t = self._clock()

        def loop() -> None:
            while not self._stop.wait(self.interval_s):
                try:
                    summary = self.sample_once()
                    self.writeback_once(summary)
                except Exception:  # noqa: BLE001 — keep sampling
                    log.exception("utilization sample failed")

        self._thread = threading.Thread(
            target=loop, name="vtpu-util-sampler", daemon=True
        )
        self._thread.start()
        from vtpu.obs.ready import readiness

        readiness("monitor").register("util_sampler", self.sampler_status)
        return True

    def stop(self, timeout: Optional[float] = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
