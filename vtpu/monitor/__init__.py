"""Node monitor (ref: cmd/vGPUmonitor).

Reads the mmap'd shared regions written by the in-container shim, exports
per-container Prometheus metrics on :9394, GCs stale container dirs, and
runs the priority feedback arbiter (which the reference ships disabled).
"""

from vtpu.monitor.pathmonitor import PathMonitor  # noqa: F401
from vtpu.monitor.shared_region import RegionFile, open_region  # noqa: F401
