"""ctypes mirror of the shared region (cpp/shared_region.h).

The monitor reads regions written by the in-container shim, exactly like
the reference's Go mirror of the C layout (cmd/vGPUmonitor/cudevshr.go:15-72
mirroring libvgpu.so's struct).  Layout must match cpp/shared_region.h
byte-for-byte — guarded by tests/test_region.py which round-trips a region
file through the C `region_tool`.
"""

from __future__ import annotations

import contextlib
import ctypes
import fcntl
import mmap
import os
import struct
from typing import Dict, List, Optional

VTPU_REGION_MAGIC = 0x76545055
VTPU_REGION_VERSION = 4
MAX_DEVICES = 16
MAX_PROCS = 64
UUID_LEN = 64

# utilization_switch throttle ladder (layout-compatible extension of the
# original binary switch — same int32 field, new value range):
#   0           enforce the configured core quota (the original default)
#   1           suspend throttling (priority arbitration, the original 1)
#   2..MAX      graduated SQUEEZE: the effective core quota halves per
#               level (level 2 = 1/2, 3 = 1/4, 4 = 1/8) — the monitor's
#               contention arbiter walks best-effort tenants down this
#               ladder before asking for eviction.  Shims that predate
#               the ladder read any value != 1 as "enforce", so a mixed
#               fleet degrades to plain enforcement, never to suspend.
THROTTLE_LEVEL_MIN = 2
THROTTLE_LEVEL_MAX = 4


def effective_core_limit(core_limit: int, switch: int) -> int:
    """Resolve the core quota a pacing path must enforce under the
    throttle ladder.  ``switch`` values below the ladder leave the quota
    alone (0 = enforce, 1 = suspend is the CALLER's branch — suspension
    must stay policy-aware).  An unthrottled tenant (quota 0 or 100)
    squeezes from a whole-chip baseline: the ladder imposes a quota on
    tenants that never had one."""
    if switch < THROTTLE_LEVEL_MIN:
        return core_limit
    level = min(switch, THROTTLE_LEVEL_MAX)
    base = core_limit if 0 < core_limit < 100 else 100
    return max(1, base >> (level - 1))


class DeviceUsage(ctypes.Structure):
    _fields_ = [
        ("program_bytes", ctypes.c_uint64),
        ("buffer_bytes", ctypes.c_uint64),
        ("total_bytes", ctypes.c_uint64),
        # host-tier bytes past quota (oversubscribe); not part of total
        ("swap_bytes", ctypes.c_uint64),
        # utilization profiling (v4): monotonic counters the monitor's
        # UtilizationSampler diffs into duty-cycle ratios, plus the
        # HBM high-watermark (ratchets up on add, never down on sub)
        ("busy_ns", ctypes.c_uint64),
        ("launches", ctypes.c_uint64),
        ("hbm_peak_bytes", ctypes.c_uint64),
    ]


class ProcSlot(ctypes.Structure):
    _fields_ = [
        ("pid", ctypes.c_int32),
        ("hostpid", ctypes.c_int32),
        ("status", ctypes.c_int32),
        ("priority", ctypes.c_int32),
        # interposer telemetry (v3): execute count + wrapper-added ns,
        # written lock-free by the owning tenant process
        ("exec_calls", ctypes.c_uint64),
        ("exec_shim_ns", ctypes.c_uint64),
        ("used", DeviceUsage * MAX_DEVICES),
    ]


class SharedRegion(ctypes.Structure):
    _fields_ = [
        ("magic", ctypes.c_uint32),
        ("version", ctypes.c_uint32),
        ("initialized", ctypes.c_int32),
        ("owner_pid", ctypes.c_int32),
        ("lock", ctypes.c_int32),
        ("num_devices", ctypes.c_int32),
        ("utilization_switch", ctypes.c_int32),
        ("recent_kernel", ctypes.c_int32),
        # device-error telemetry (XID-analog): consecutive execute errors
        # + cumulative count, written by the shim's execute path
        ("error_streak", ctypes.c_int32),
        ("exec_errors", ctypes.c_int32),
        ("uuids", (ctypes.c_char * UUID_LEN) * MAX_DEVICES),
        ("limit_bytes", ctypes.c_uint64 * MAX_DEVICES),
        ("core_limit", ctypes.c_int32 * MAX_DEVICES),
        ("proc_num", ctypes.c_int32),
        ("_pad", ctypes.c_int32),
        ("reserved", ctypes.c_uint64 * 8),
        ("procs", ProcSlot * MAX_PROCS),
    ]


REGION_SIZE = ctypes.sizeof(SharedRegion)


# -- legacy v3 layout (read path for regions written by pre-v4 shims; a
# long-running tenant keeps its region across a monitor upgrade, so the
# monitor must keep reading it — the new counters read as 0 there) -------

class _DeviceUsageV3(ctypes.Structure):
    _fields_ = DeviceUsage._fields_[:4]


class _ProcSlotV3(ctypes.Structure):
    _fields_ = ProcSlot._fields_[:6] + [("used", _DeviceUsageV3 * MAX_DEVICES)]


class _SharedRegionV3(ctypes.Structure):
    _fields_ = SharedRegion._fields_[:16] + [("procs", _ProcSlotV3 * MAX_PROCS)]


REGION_SIZE_V3 = ctypes.sizeof(_SharedRegionV3)


class RegionFile:
    """mmap a region file read-write (ref mmapcachefile cudevshr.go:112-127).
    The monitor only mutates utilization_switch / hostpid fields."""

    def __init__(self, path: str, create: bool = False) -> None:
        self.path = path
        flags = os.O_RDWR | (os.O_CREAT if create else 0)
        fd = os.open(path, flags, 0o666)
        try:
            size = os.fstat(fd).st_size
            # sniff magic+version BEFORE sizing: a v3 region written by a
            # pre-v4 shim is smaller than the current layout and must be
            # mapped with the legacy struct, not grown and misread
            header = os.pread(fd, 8, 0) if size >= 8 else b""
            magic0, version0 = (
                struct.unpack("=II", header) if len(header) == 8 else (0, 0)
            )
            self._legacy = magic0 == VTPU_REGION_MAGIC and version0 == 3
            layout = _SharedRegionV3 if self._legacy else SharedRegion
            region_size = ctypes.sizeof(layout)
            if size < region_size:
                if not create or self._legacy:
                    raise ValueError(f"{path}: too small for a vtpu region")
                os.ftruncate(fd, region_size)
            self._mm = mmap.mmap(fd, region_size)
        except BaseException:
            os.close(fd)
            raise
        # fd stays open: it carries the cross-process flock that both this
        # mirror and the C library (cpp/shared_region.cc) take around every
        # mutation — same file, same lock, released by the kernel on death
        self._fd = fd
        self.region = layout.from_buffer(self._mm)
        if create and self.region.magic == 0:
            self.region.magic = VTPU_REGION_MAGIC
            self.region.version = VTPU_REGION_VERSION
            self.region.initialized = 1
        magic, version = self.region.magic, self.region.version
        if magic != VTPU_REGION_MAGIC:
            self.close()
            raise ValueError(f"{path}: bad magic {magic:#x}")
        if version != (3 if self._legacy else VTPU_REGION_VERSION):
            self.close()
            raise ValueError(f"{path}: region version {version}")
        self.version = version

    @contextlib.contextmanager
    def _locked(self):
        fcntl.flock(self._fd, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(self._fd, fcntl.LOCK_UN)

    # -- read side -------------------------------------------------------
    def device_uuids(self) -> List[str]:
        r = self.region
        return [r.uuids[i].value.decode() for i in range(r.num_devices)]

    def limits(self) -> List[int]:
        r = self.region
        return [r.limit_bytes[i] for i in range(r.num_devices)]

    def core_limits(self) -> List[int]:
        r = self.region
        return [r.core_limit[i] for i in range(r.num_devices)]

    def usage(self) -> List[Dict[str, int]]:
        """Per-device totals across live procs (ref getvGPUMemoryInfo)."""
        with self._locked():
            return self._usage_nolock()

    def _usage_nolock(self) -> List[Dict[str, int]]:
        r = self.region
        legacy = self._legacy
        out = []
        for d in range(r.num_devices):
            buf = prog = swap = busy = launches = peak = 0
            for p in range(MAX_PROCS):
                if r.procs[p].status == 1:
                    u = r.procs[p].used[d]
                    buf += u.buffer_bytes
                    prog += u.program_bytes
                    swap += u.swap_bytes
                    if not legacy:
                        busy += u.busy_ns
                        launches += u.launches
                        # summed per-proc peaks: an upper bound on the
                        # container's true simultaneous peak (procs may
                        # peak at different times), monotone like them
                        peak += u.hbm_peak_bytes
            out.append(
                {"buffer": buf, "program": prog, "total": buf + prog,
                 "swap": swap, "busy_ns": busy, "launches": launches,
                 "hbm_peak": peak}
            )
        return out

    def live_procs(self) -> List[Dict[str, int]]:
        r = self.region
        out = []
        legacy = self._legacy
        for p in range(MAX_PROCS):
            slot = r.procs[p]
            if slot.status == 1:
                out.append(
                    {
                        "pid": slot.pid,
                        "hostpid": slot.hostpid,
                        "priority": slot.priority,
                        "exec_calls": slot.exec_calls,
                        "exec_shim_ns": slot.exec_shim_ns,
                        "total_bytes": sum(
                            slot.used[d].total_bytes for d in range(r.num_devices)
                        ),
                        "busy_ns": 0 if legacy else sum(
                            slot.used[d].busy_ns for d in range(r.num_devices)
                        ),
                        "launches": 0 if legacy else sum(
                            slot.used[d].launches for d in range(r.num_devices)
                        ),
                    }
                )
        return out

    # -- monitor write side ---------------------------------------------
    def set_utilization_switch(self, value: int) -> None:
        self.region.utilization_switch = value

    def set_hostpid(self, pid: int, hostpid: int) -> None:
        with self._locked():
            self._set_hostpid_nolock(pid, hostpid)

    def _set_hostpid_nolock(self, pid: int, hostpid: int) -> None:
        r = self.region
        for p in range(MAX_PROCS):
            if r.procs[p].status == 1 and r.procs[p].pid == pid:
                r.procs[p].hostpid = hostpid

    def incr_recent_kernel(self, n: int = 1) -> None:
        """Locked kernel-launch count (shim dispatch path): the counter is
        contended by every tenant's dispatch AND the monitor's decay, so a
        bare += would lose increments."""
        with self._locked():
            self.region.recent_kernel += n

    def record_launch(self, pid: int, dev: int, busy_ns: int, n: int = 1) -> None:
        """One dispatch's utilization record under a single flock: the
        shared ``recent_kernel`` activity counter (what incr_recent_kernel
        bumps) plus the v4 per-proc per-device monotonic launch count and
        device-busy estimate the monitor's UtilizationSampler diffs.  On a
        legacy v3 region only the activity counter moves."""
        with self._locked():
            r = self.region
            r.recent_kernel += n
            if self._legacy or not (0 <= dev < MAX_DEVICES):
                return
            for p in range(MAX_PROCS):
                if r.procs[p].status == 1 and r.procs[p].pid == pid:
                    r.procs[p].used[dev].busy_ns += max(0, int(busy_ns))
                    r.procs[p].used[dev].launches += n
                    return

    def record_exec_result(self, ok: bool) -> None:
        """Execute outcome feed (the XID-analog health stream): a success
        resets the consecutive-error streak, a failure bumps it plus the
        cumulative error count."""
        with self._locked():
            if ok:
                self.region.error_streak = 0
            else:
                self.region.error_streak += 1
                self.region.exec_errors += 1

    def decay_recent_kernel(self) -> int:
        """ref Observe (feedback.go): halve the activity counter, return the
        pre-decay value."""
        with self._locked():
            v = self.region.recent_kernel
            self.region.recent_kernel = v // 2
            return v

    # -- writer side (used by the cooperative Python shim) ----------------
    def set_devices(self, uuids: List[str], limits: List[int], cores: List[int]) -> None:
        with self._locked():
            self._set_devices_nolock(uuids, limits, cores)

    def _set_devices_nolock(self, uuids: List[str], limits: List[int], cores: List[int]) -> None:
        r = self.region
        if r.num_devices == 0:
            n = min(len(uuids), MAX_DEVICES)
            r.num_devices = n
            for i in range(n):
                r.uuids[i].value = uuids[i].encode()[: UUID_LEN - 1]
                r.limit_bytes[i] = limits[i]
                r.core_limit[i] = cores[i]

    def register_proc(self, pid: int, priority: int = 0,
                      fresh: bool = False) -> int:
        """``fresh=True`` is for a process KNOWN to be newly started: a
        pid-matching slot left by a dead predecessor (container pid
        recycled) gets its usage/telemetry cleared instead of inherited
        (mirrors vtpu_region_register_proc_fresh)."""
        with self._locked():
            return self._register_proc_nolock(pid, priority, fresh)

    def _register_proc_nolock(self, pid: int, priority: int = 0,
                              fresh: bool = False) -> int:
        r = self.region
        for p in range(MAX_PROCS):
            if r.procs[p].status == 1 and r.procs[p].pid == pid:
                if fresh:
                    ctypes.memset(
                        ctypes.byref(r.procs[p].used), 0,
                        ctypes.sizeof(r.procs[p].used),
                    )
                    r.procs[p].exec_calls = 0
                    r.procs[p].exec_shim_ns = 0
                    r.procs[p].hostpid = 0
                    r.procs[p].priority = priority
                return p
        for p in range(MAX_PROCS):
            if r.procs[p].status == 0:
                ctypes.memset(ctypes.byref(r.procs[p]), 0, ctypes.sizeof(ProcSlot))
                r.procs[p].pid = pid
                r.procs[p].status = 1
                r.procs[p].priority = priority
                r.proc_num += 1
                return p
        return -1

    def reap_dead(self, alive) -> int:
        """Free slots whose process is gone (ref clear_proc_slot_nolock /
        fix_lock_shrreg cleanup): a crashed tenant must not pin its quota
        bytes forever.  ``alive(slot_dict)`` returns True (keep), False
        (reap), or None (unknown — keep; e.g. the monitor cannot verify
        an in-container pid whose hostpid is unresolved).  Returns the
        number of slots freed."""
        freed = 0
        with self._locked():
            r = self.region
            for p in range(MAX_PROCS):
                if r.procs[p].status != 1:
                    continue
                verdict = alive(
                    {"pid": r.procs[p].pid, "hostpid": r.procs[p].hostpid}
                )
                if verdict is False:
                    ctypes.memset(
                        ctypes.byref(r.procs[p]), 0, ctypes.sizeof(ProcSlot)
                    )
                    if r.proc_num > 0:
                        r.proc_num -= 1
                    freed += 1
        return freed

    def try_add(self, pid: int, dev: int, bytes_: int, kind: str = "buffer",
                limit: int = 0, oversubscribe: bool = False) -> bool:
        """Atomic check-and-add under one flock (the check_oom analog,
        mirroring vtpu_region_try_add): returns False when adding would
        exceed ``limit`` (0 = unlimited)."""
        with self._locked():
            self._register_proc_nolock(pid)
            if limit and not oversubscribe:
                used = sum(d["total"] for d in self._usage_nolock()[dev:dev + 1])
                if used + bytes_ > limit:
                    return False
            self._add_usage_nolock(pid, dev, bytes_, kind)
            return True

    def add_usage(self, pid: int, dev: int, bytes_: int, kind: str = "buffer") -> None:
        with self._locked():
            self._add_usage_nolock(pid, dev, bytes_, kind)

    def _add_usage_nolock(self, pid: int, dev: int, bytes_: int, kind: str = "buffer") -> None:
        r = self.region
        for p in range(MAX_PROCS):
            if r.procs[p].status == 1 and r.procs[p].pid == pid:
                u = r.procs[p].used[dev]
                if kind == "program":
                    u.program_bytes += bytes_
                elif kind == "swap":
                    u.swap_bytes += bytes_
                else:
                    u.buffer_bytes += bytes_
                u.total_bytes = u.program_bytes + u.buffer_bytes
                if not self._legacy and u.total_bytes > u.hbm_peak_bytes:
                    u.hbm_peak_bytes = u.total_bytes  # v4 watermark ratchet
                return

    def sub_usage(self, pid: int, dev: int, bytes_: int, kind: str = "buffer") -> None:
        with self._locked():
            self._sub_usage_nolock(pid, dev, bytes_, kind)

    def _sub_usage_nolock(self, pid: int, dev: int, bytes_: int, kind: str = "buffer") -> None:
        r = self.region
        for p in range(MAX_PROCS):
            if r.procs[p].status == 1 and r.procs[p].pid == pid:
                u = r.procs[p].used[dev]
                if kind == "program":
                    u.program_bytes = max(0, u.program_bytes - bytes_)
                elif kind == "swap":
                    u.swap_bytes = max(0, u.swap_bytes - bytes_)
                else:
                    u.buffer_bytes = max(0, u.buffer_bytes - bytes_)
                u.total_bytes = u.program_bytes + u.buffer_bytes
                return

    def close(self) -> None:
        # release the ctypes view before unmapping
        self.region = None  # type: ignore[assignment]
        self._mm.close()
        os.close(self._fd)


def open_region(path: str, create: bool = False) -> Optional[RegionFile]:
    try:
        return RegionFile(path, create=create)
    except (OSError, ValueError):
        return None
