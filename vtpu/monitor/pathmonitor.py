"""Cache-dir scanner and GC (ref: cmd/vGPUmonitor/pathmonitor.go:29-114).

Walks /usr/local/vtpu/containers/<podUID>_<n>/, mmaps each vtpu.cache into
a RegionFile, validates the owning pod still exists, and GCs dirs whose pod
is gone and whose mtime is stale (300 s).
"""

from __future__ import annotations

import logging
import os
import shutil
import time
from typing import Dict, Optional

from vtpu.monitor.shared_region import RegionFile, open_region

log = logging.getLogger(__name__)

GC_GRACE_S = 300  # ref pathmonitor.go:83-92
REGION_FILENAME = "vtpu.cache"


class ContainerEntry:
    def __init__(self, dirname: str, path: str, region: Optional[RegionFile]) -> None:
        self.dirname = dirname          # "<podUID>_<n>"
        self.path = path
        self.region = region

    @property
    def pod_uid(self) -> str:
        return self.dirname.rsplit("_", 1)[0]


class PathMonitor:
    def __init__(self, root: str) -> None:
        self.root = root
        self.entries: Dict[str, ContainerEntry] = {}

    def scan(self, known_pod_uids: Optional[set] = None) -> Dict[str, ContainerEntry]:
        """One monitorpath pass (ref :72-114): pick up new dirs, drop+GC
        stale ones.  ``known_pod_uids`` of None skips pod validation."""
        if not os.path.isdir(self.root):
            return self.entries
        seen = set()
        for name in sorted(os.listdir(self.root)):
            d = os.path.join(self.root, name)
            if not os.path.isdir(d):
                continue
            seen.add(name)
            if name not in self.entries:
                cache = os.path.join(d, REGION_FILENAME)
                region = open_region(cache) if os.path.exists(cache) else None
                self.entries[name] = ContainerEntry(name, d, region)
                if region:
                    log.info("monitoring new container region %s", name)
            elif self.entries[name].region is None:
                # region file may appear after the dir (mount then first touch)
                cache = os.path.join(d, REGION_FILENAME)
                if os.path.exists(cache):
                    self.entries[name].region = open_region(cache)
            if known_pod_uids is not None:
                entry = self.entries[name]
                if entry.pod_uid not in known_pod_uids:
                    age = time.time() - os.path.getmtime(d)
                    if age > GC_GRACE_S:
                        log.info("GC stale container dir %s (age %.0fs)", name, age)
                        if entry.region:
                            entry.region.close()
                        shutil.rmtree(d, ignore_errors=True)
                        self.entries.pop(name, None)
                        seen.discard(name)
        for name in list(self.entries):
            if name not in seen:
                e = self.entries.pop(name)
                if e.region:
                    e.region.close()
        return self.entries

    def close(self) -> None:
        for e in self.entries.values():
            if e.region:
                e.region.close()
        self.entries.clear()
