"""Cache-dir scanner and GC (ref: cmd/vGPUmonitor/pathmonitor.go:29-114).

Walks /usr/local/vtpu/containers/<podUID>_<n>/, mmaps each vtpu.cache into
a RegionFile, validates the owning pod still exists, and GCs dirs whose pod
is gone and whose mtime is stale (300 s).

Hardened against scan races: kubelet (or the GC of a peer monitor) can
remove a container dir between ``listdir`` and the per-dir stat/open/GC —
one vanished dir must never abort the whole pass.  Per-dir failures are
swallowed, counted on ``vtpu_pathmonitor_scan_failures_total``, and the
entry retries next tick; GC'd dirs count on
``vtpu_pathmonitor_gc_dirs_total``.
"""

from __future__ import annotations

import logging
import os
import shutil
import time
from typing import Dict, Optional

from vtpu import obs
from vtpu.monitor.shared_region import RegionFile, open_region
from vtpu.obs.events import EventType, emit

log = logging.getLogger(__name__)

GC_GRACE_S = 300  # ref pathmonitor.go:83-92
REGION_FILENAME = "vtpu.cache"

_MON = obs.registry("monitor")
_SCANS = _MON.counter(
    "vtpu_pathmonitor_scans_total", "Pathmonitor scan passes completed"
)
_SCAN_FAILURES = _MON.counter(
    "vtpu_pathmonitor_scan_failures_total",
    "Per-dir scan steps that failed (dir vanished mid-pass, unreadable "
    "region, stat error) — the pass continues past each one",
)
_GC_DIRS = _MON.counter(
    "vtpu_pathmonitor_gc_dirs_total",
    "Stale container dirs garbage-collected (pod gone + mtime past grace)",
)


class ContainerEntry:
    def __init__(self, dirname: str, path: str, region: Optional[RegionFile]) -> None:
        self.dirname = dirname          # "<podUID>_<n>"
        self.path = path
        self.region = region

    @property
    def pod_uid(self) -> str:
        return self.dirname.rsplit("_", 1)[0]


class PathMonitor:
    def __init__(self, root: str) -> None:
        self.root = root
        self.entries: Dict[str, ContainerEntry] = {}

    def scan(self, known_pod_uids: Optional[set] = None) -> Dict[str, ContainerEntry]:
        """One monitorpath pass (ref :72-114): pick up new dirs, drop+GC
        stale ones.  ``known_pod_uids`` of None skips pod validation."""
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return self.entries  # root missing / unreadable: nothing to do
        seen = set()
        for name in names:
            d = os.path.join(self.root, name)
            try:
                self._scan_one(name, d, known_pod_uids, seen)
            except OSError:
                # dir vanished (or turned unreadable) between listdir and
                # the per-dir work — skip it, keep the pass alive
                _SCAN_FAILURES.inc()
                log.debug("scan: %s failed mid-pass", name, exc_info=True)
        for name in list(self.entries):
            if name not in seen:
                e = self.entries.pop(name)
                if e.region:
                    e.region.close()
        _SCANS.inc()
        return self.entries

    def _scan_one(
        self, name: str, d: str, known_pod_uids: Optional[set], seen: set
    ) -> None:
        if not os.path.isdir(d):
            return
        seen.add(name)
        if name not in self.entries:
            cache = os.path.join(d, REGION_FILENAME)
            region = open_region(cache) if os.path.exists(cache) else None
            entry = self.entries[name] = ContainerEntry(name, d, region)
            if region:
                log.info("monitoring new container region %s", name)
                emit(EventType.REGION_ATTACHED, "monitor",
                     pod=entry.pod_uid, ctr=name)
        elif self.entries[name].region is None:
            # region file may appear after the dir (mount then first touch)
            cache = os.path.join(d, REGION_FILENAME)
            if os.path.exists(cache):
                self.entries[name].region = open_region(cache)
                emit(EventType.REGION_ATTACHED, "monitor",
                     pod=self.entries[name].pod_uid, ctr=name)
        if known_pod_uids is not None:
            entry = self.entries[name]
            if entry.pod_uid not in known_pod_uids:
                try:
                    age = time.time() - os.path.getmtime(d)
                except OSError:
                    # dir vanished between isdir and getmtime: treat as
                    # already gone — drop the entry, no GC needed
                    _SCAN_FAILURES.inc()
                    if entry.region:
                        entry.region.close()
                    self.entries.pop(name, None)
                    seen.discard(name)
                    return
                if age > GC_GRACE_S:
                    log.info("GC stale container dir %s (age %.0fs)", name, age)
                    if entry.region:
                        entry.region.close()
                    shutil.rmtree(d, ignore_errors=True)
                    self.entries.pop(name, None)
                    seen.discard(name)
                    _GC_DIRS.inc()
                    emit(EventType.REGION_GC, "monitor",
                         pod=entry.pod_uid, ctr=name, age_s=round(age, 1))

    def close(self) -> None:
        for e in self.entries.values():
            if e.region:
                e.region.close()
        self.entries.clear()
