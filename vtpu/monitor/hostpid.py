"""Container-pid → host-pid mapping (ref cmd/vGPUmonitor/feedback.go:83-162).

The reference's ``setHostPid`` joins NVML's running-process list against
cgroupfs ``tasks`` files to fill each shared-region slot's ``hostpid``.
The monitor daemonset runs with hostPID (charts/vtpu daemonsets), so the
TPU-native equivalent needs no device library: every tenant process is
visible in the host ``/proc``, where

  * ``/proc/<hostpid>/status`` carries ``NSpid:`` — the pid-namespace
    chain, host pid first, the pid *inside the container's namespace*
    last; and
  * ``/proc/<hostpid>/cgroup`` names the owning pod
    (``...pod<UID>...``), which disambiguates identical in-container
    pids across pods.

``fill_hostpids`` walks the scanned container regions and writes the
resolved host pid into each live proc slot via the region's
``set_hostpid`` (shared_region.h:46 — the field the shim leaves for the
monitor to fill), so node-side tooling (noderpc, metrics, operators) can
correlate region procs with host processes.
"""

from __future__ import annotations

import logging
import os
import re
from typing import Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

# pod UID inside a cgroup path: plain cgroupfs ("/kubepods/burstable/
# pod<uid>/...") or systemd-escaped ("kubepods-burstable-pod<uid with _
# for ->.slice")
_POD_RE = re.compile(r"pod([0-9a-fA-F_\-]{36})")


def _nspid_chain(status_text: str) -> List[int]:
    """NSpid line of /proc/<pid>/status → [host, ..., innermost]."""
    for line in status_text.splitlines():
        if line.startswith("NSpid:"):
            try:
                return [int(t) for t in line.split()[1:]]
            except ValueError:
                return []
    return []


def _cgroup_pod_uid(cgroup_text: str) -> Optional[str]:
    m = _POD_RE.search(cgroup_text)
    if not m:
        return None
    return m.group(1).replace("_", "-").lower()


def scan_host_procs(proc_root: str = "/proc") -> List[Tuple[int, int, Optional[str]]]:
    """Enumerate host processes → (hostpid, container_pid, pod_uid).

    Only processes in a child pid namespace are returned (NSpid chain
    length > 1) — host-native processes cannot be region tenants."""
    out: List[Tuple[int, int, Optional[str]]] = []
    try:
        names = os.listdir(proc_root)
    except OSError:
        return out
    for name in names:
        if not name.isdigit():
            continue
        base = os.path.join(proc_root, name)
        try:
            with open(os.path.join(base, "status")) as f:
                chain = _nspid_chain(f.read())
        except OSError:
            continue
        if len(chain) < 2:
            continue
        pod_uid = None
        try:
            with open(os.path.join(base, "cgroup")) as f:
                pod_uid = _cgroup_pod_uid(f.read())
        except OSError:
            pass
        out.append((int(name), chain[-1], pod_uid))
    return out


def reap_dead_by_hostpid(pathmon, proc_root: str = "/proc") -> int:
    """Free region slots whose HOST process is gone (ref
    clear_proc_slot_nolock — the reference's C library reaps dead procs;
    on the host side only hostpid-resolved slots are verifiable, so
    unresolved ones are left alone; the in-container shim reaps those on
    its next client create).  Returns slots freed across all regions."""
    freed = 0
    for entry in pathmon.entries.values():
        region = entry.region
        if region is None:
            continue

        def host_alive(slot):
            hp = slot.get("hostpid")
            if not hp:
                return None  # unverifiable from the host namespace
            # bare /proc/<hostpid> existence is NOT liveness: the kernel
            # recycles pids, and a recycled hostpid would pin a dead
            # tenant's quota forever.  The slot is alive only if that
            # host process still maps to the recorded in-container pid.
            try:
                with open(os.path.join(proc_root, str(hp), "status")) as f:
                    chain = _nspid_chain(f.read())
            except OSError:
                return False  # process gone
            if len(chain) < 2 or chain[-1] != slot["pid"]:
                return False  # hostpid recycled to an unrelated process
            return True

        freed += region.reap_dead(host_alive)
    return freed


def fill_hostpids(pathmon, proc_root: str = "/proc") -> int:
    """Resolve and write hostpid for every live region slot that lacks
    one.  A slot matches a host process when the in-container pids agree
    AND the pod UIDs agree (when the cgroup names one); an in-container
    pid with several candidate host processes — whether across pods or
    between two containers of the SAME pod (each container has its own
    pid namespace, so sibling containers routinely share pid 1) — is
    left unresolved rather than guessed.  Returns the number of slots
    filled."""
    host = scan_host_procs(proc_root)
    by_cpid: Dict[int, List[Tuple[int, Optional[str]]]] = {}
    for hostpid, cpid, pod_uid in host:
        by_cpid.setdefault(cpid, []).append((hostpid, pod_uid))
    filled = 0
    for entry in pathmon.entries.values():
        region = entry.region
        if region is None:
            continue
        pod_uid = entry.pod_uid.lower()
        for proc in region.live_procs():
            if proc.get("hostpid"):
                continue
            cands = by_cpid.get(proc["pid"], [])
            with_pod = [h for h, p in cands if p == pod_uid]
            if len(with_pod) == 1:
                chosen = with_pod[0]
            elif not with_pod and len(cands) == 1 and cands[0][1] is None:
                chosen = cands[0][0]
            else:
                continue
            region.set_hostpid(proc["pid"], chosen)
            filled += 1
            log.debug(
                "hostpid: %s pid %d → host pid %d",
                entry.dirname, proc["pid"], chosen,
            )
    return filled
