"""Real Kubernetes REST client over the stdlib (no client-go equivalent here).

Ref: pkg/k8sutil/client.go:33-48 — in-cluster config with $KUBECONFIG
fallback.  Implements exactly the verbs the framework needs: get/list nodes
and pods, merge-patch annotations, create pod bindings.  Patches use
``application/merge-patch+json`` so a ``null`` value deletes an annotation —
the same semantics the fake client implements.
"""

from __future__ import annotations

import json
import os
import ssl
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional

from vtpu.k8s.errors import Conflict, NotFound  # noqa: E402
from vtpu.utils.envs import env_str

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class ApiError(Exception):
    def __init__(self, status: int, body: str) -> None:
        super().__init__(f"kubernetes API error {status}: {body[:200]}")
        self.status = status
        self.body = body


class Client:
    """Token-auth REST client. In-cluster by default; set ``base_url`` /
    ``token`` / ``ca_file`` explicitly for out-of-cluster use (e.g. pointing
    at a kind cluster or a test apiserver)."""

    def __init__(
        self,
        base_url: Optional[str] = None,
        token: Optional[str] = None,
        ca_file: Optional[str] = None,
        insecure: bool = False,
    ) -> None:
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "not in-cluster (KUBERNETES_SERVICE_HOST unset) and no base_url given"
                )
            base_url = f"https://{host}:{port}"
        self.base_url = base_url.rstrip("/")
        if token is None and os.path.exists(os.path.join(_SA_DIR, "token")):
            with open(os.path.join(_SA_DIR, "token")) as f:
                token = f.read().strip()
        self.token = token
        if ca_file is None and os.path.exists(os.path.join(_SA_DIR, "ca.crt")):
            ca_file = os.path.join(_SA_DIR, "ca.crt")
        if insecure:
            self._ctx: Optional[ssl.SSLContext] = ssl._create_unverified_context()
        elif ca_file:
            self._ctx = ssl.create_default_context(cafile=ca_file)
        else:
            self._ctx = ssl.create_default_context() if self.base_url.startswith("https") else None

    # -- low level --------------------------------------------------------
    def _request(
        self,
        method: str,
        path: str,
        body: Optional[dict] = None,
        content_type: str = "application/json",
        params: Optional[Dict[str, str]] = None,
    ) -> dict:
        url = self.base_url + path
        if params:
            url += "?" + urllib.parse.urlencode(params)
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", content_type)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, context=self._ctx, timeout=30) as resp:
                raw = resp.read()
        except urllib.error.HTTPError as e:
            raise ApiError(e.code, e.read().decode(errors="replace")) from e
        return json.loads(raw) if raw else {}

    # -- nodes ------------------------------------------------------------
    def get_node(self, name: str) -> dict:
        return self._request("GET", f"/api/v1/nodes/{name}")

    def create_node(self, node: dict) -> dict:
        """POST a Node object — the leader elector creates its dedicated
        election Node on demand (vtpu/scheduler/shard.py); a kubelet-less
        virtual Node is a legal API object."""
        return self._request("POST", "/api/v1/nodes", body=node)

    def list_nodes(self) -> List[dict]:
        return self._request("GET", "/api/v1/nodes").get("items", [])

    def patch_node_annotations(
        self,
        name: str,
        annotations: Dict[str, Optional[str]],
        resource_version: Optional[str] = None,
    ) -> dict:
        # ref: PatchNodeAnnotations (util.go:262-284).  Unconditional updates
        # use merge-patch; conditional ones (the node lock) use a JSON patch
        # whose leading `test` op on resourceVersion makes the apiserver
        # reject the write if the node changed since it was read — the
        # optimistic concurrency the reference gets from client-go Update().
        if resource_version is None:
            patch = {"metadata": {"annotations": annotations}}
            return self._request(
                "PATCH", f"/api/v1/nodes/{name}", patch, "application/merge-patch+json"
            )
        # Re-read to learn whether the annotations map exists at all — a
        # never-annotated node has metadata.annotations == null and a
        # json-patch `add` under the missing map would 422 forever.  The
        # resourceVersion `test` op pins the exact state we read, so the
        # bootstrap `add` of the map itself cannot race.
        node = self.get_node(name)
        if node["metadata"].get("resourceVersion") != resource_version:
            raise Conflict(f"node {name}: resourceVersion changed since read")
        ops = [
            {"op": "test", "path": "/metadata/resourceVersion", "value": resource_version}
        ]
        if node["metadata"].get("annotations") is None:
            ops.append({"op": "add", "path": "/metadata/annotations", "value": {}})
        for k, v in annotations.items():
            path = "/metadata/annotations/" + k.replace("~", "~0").replace("/", "~1")
            if v is None:
                ops.append({"op": "remove", "path": path})
            else:
                ops.append({"op": "add", "path": path, "value": v})
        try:
            return self._request(
                "PATCH", f"/api/v1/nodes/{name}", ops, "application/json-patch+json"
            )
        except ApiError as e:
            if e.status in (409, 422):
                raise Conflict(str(e)) from e
            raise

    # -- coordination.k8s.io/v1 Lease objects -----------------------------
    # The kube-native leader-election primitive.  update_lease is a PUT, so
    # the apiserver rejects a stale metadata.resourceVersion with 409 — the
    # same optimistic CAS the annotation-lease elector built by hand.
    def get_lease(self, name: str, namespace: str = "vtpu-system") -> dict:
        try:
            return self._request(
                "GET",
                f"/apis/coordination.k8s.io/v1/namespaces/{namespace}/leases/{name}",
            )
        except ApiError as e:
            if e.status == 404:
                raise NotFound(f"lease {namespace}/{name}") from e
            raise

    def create_lease(self, lease: dict) -> dict:
        ns = lease["metadata"].get("namespace", "vtpu-system")
        try:
            return self._request(
                "POST",
                f"/apis/coordination.k8s.io/v1/namespaces/{ns}/leases",
                body=lease,
            )
        except ApiError as e:
            if e.status == 409:
                # AlreadyExists — the loser of a creation race becomes a
                # follower, exactly like the fake client
                raise Conflict(str(e)) from e
            raise

    def update_lease(
        self, name: str, lease: dict, namespace: str = "vtpu-system"
    ) -> dict:
        try:
            return self._request(
                "PUT",
                f"/apis/coordination.k8s.io/v1/namespaces/{namespace}/leases/{name}",
                body=lease,
            )
        except ApiError as e:
            if e.status == 404:
                raise NotFound(f"lease {namespace}/{name}") from e
            if e.status == 409:
                raise Conflict(str(e)) from e
            raise

    # -- pods -------------------------------------------------------------
    def get_pod(self, namespace: str, name: str) -> dict:
        return self._request("GET", f"/api/v1/namespaces/{namespace}/pods/{name}")

    def list_pods(self, node_name: Optional[str] = None) -> List[dict]:
        params = {}
        if node_name is not None:
            params["fieldSelector"] = f"spec.nodeName={node_name}"
        return self._request("GET", "/api/v1/pods", params=params or None).get("items", [])

    def list_pods_raw(self) -> dict:
        """Full list response incl. ``metadata.resourceVersion`` — the
        point to resume a watch from."""
        return self._request("GET", "/api/v1/pods")

    def watch_pods(self, resource_version: Optional[str] = None,
                   timeout_s: float = 30.0):
        """Stream pod change events (the informer path, replacing the
        O(cluster) full re-list every poll): yields ``(type, pod)`` for
        ADDED / MODIFIED / DELETED until the server closes the watch
        window.  Callers re-list + re-watch on exhaustion or error."""
        params = {"watch": "true", "timeoutSeconds": str(int(timeout_s))}
        if resource_version:
            params["resourceVersion"] = str(resource_version)
        url = self.base_url + "/api/v1/pods?" + urllib.parse.urlencode(params)
        req = urllib.request.Request(url, method="GET")
        req.add_header("Accept", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(
                req, context=self._ctx, timeout=timeout_s + 30
            ) as resp:
                for line in resp:
                    line = line.strip()
                    if not line:
                        continue
                    ev = json.loads(line)
                    yield ev["type"], ev["object"]
        except urllib.error.HTTPError as e:
            raise ApiError(e.code, e.read().decode(errors="replace")) from e

    def patch_pod_annotations(
        self, namespace: str, name: str, annotations: Dict[str, Optional[str]]
    ) -> dict:
        patch = {"metadata": {"annotations": annotations}}
        return self._request(
            "PATCH",
            f"/api/v1/namespaces/{namespace}/pods/{name}",
            patch,
            "application/merge-patch+json",
        )

    def bind_pod(self, namespace: str, name: str, node_name: str) -> None:
        # ref: scheduler.go:402-442 — POST Binding subresource
        binding = {
            "apiVersion": "v1",
            "kind": "Binding",
            "metadata": {"name": name, "namespace": namespace},
            "target": {"apiVersion": "v1", "kind": "Node", "name": node_name},
        }
        self._request(
            "POST", f"/api/v1/namespaces/{namespace}/pods/{name}/binding", binding
        )

    def delete_pod(self, namespace: str, name: str) -> None:
        self._request("DELETE", f"/api/v1/namespaces/{namespace}/pods/{name}")


def new_client() -> Client:
    """In-cluster, else $VTPU_APISERVER + $VTPU_TOKEN (test/dev).

    TLS verification stays ON by default; point $VTPU_CA_FILE at the
    cluster CA, or set $VTPU_INSECURE_SKIP_TLS_VERIFY=true explicitly (the
    same opt-in shape as kubectl's --insecure-skip-tls-verify)."""
    if os.environ.get("KUBERNETES_SERVICE_HOST"):
        return Client()
    base = env_str("VTPU_APISERVER")
    if not base:
        raise RuntimeError("set VTPU_APISERVER for out-of-cluster use")
    insecure = env_str("VTPU_INSECURE_SKIP_TLS_VERIFY").lower() in (
        "1",
        "true",
        "yes",
    )
    return Client(
        base_url=base,
        token=env_str("VTPU_TOKEN") or None,
        ca_file=env_str("VTPU_CA_FILE") or None,
        insecure=insecure,
    )
