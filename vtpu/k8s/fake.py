"""In-memory fake Kubernetes client.

Analog of client-go's fake.NewSimpleClientset used by the reference's tests
(ref: SURVEY.md §4).  Implements the same surface as `vtpu.k8s.client.Client`
with merge-patch annotation semantics (value None deletes the key), so the
entire register→filter→bind→allocate handshake runs in-process without a
cluster.
"""

from __future__ import annotations

import copy
from typing import Callable, Dict, List, Optional


from vtpu.analysis.witness import make_lock
from vtpu.k8s.errors import Conflict, NotFound  # noqa: F401  (re-export)


class FakeClient:
    def __init__(self) -> None:
        self._lock = make_lock("k8s.fake", reentrant=True)
        self._nodes: Dict[str, dict] = {}
        self._pods: Dict[str, dict] = {}  # key: ns/name
        self._leases: Dict[str, dict] = {}  # key: ns/name
        self._rv = 0
        # hooks for tests: called after each mutation with (kind, obj)
        self.on_mutate: Optional[Callable[[str, dict], None]] = None

    # -- helpers ----------------------------------------------------------
    def _bump(self, obj: dict) -> None:
        self._rv += 1
        obj["metadata"]["resourceVersion"] = str(self._rv)

    @staticmethod
    def _key(namespace: str, name: str) -> str:
        return f"{namespace}/{name}"

    def _notify(self, kind: str, obj: dict) -> None:
        if self.on_mutate is not None:
            self.on_mutate(kind, copy.deepcopy(obj))

    # -- nodes ------------------------------------------------------------
    def create_node(self, node: dict) -> dict:
        with self._lock:
            name = node["metadata"]["name"]
            if name in self._nodes:
                # apiserver semantics: create of an existing object is
                # 409 AlreadyExists, never an upsert.  The silent
                # overwrite clobbered concurrent mutations — a stale
                # leader elector's lease-object bootstrap could destroy
                # the winner's fresh lease annotation and elect two
                # leaders (a race the real apiserver cannot produce).
                raise Conflict(f"node {name} already exists")
            self._bump(node)
            self._nodes[name] = copy.deepcopy(node)
            self._notify("Node", node)
            return copy.deepcopy(node)

    def get_node(self, name: str) -> dict:
        with self._lock:
            if name not in self._nodes:
                raise NotFound(f"node {name}")
            return copy.deepcopy(self._nodes[name])

    def list_nodes(self) -> List[dict]:
        with self._lock:
            return [copy.deepcopy(n) for n in self._nodes.values()]

    def delete_node(self, name: str) -> None:
        """Remove a node (cluster-scale churn: nodes die mid-run)."""
        with self._lock:
            node = self._nodes.pop(name, None)
            if node is not None:
                self._notify("NodeDeleted", node)

    def patch_node_annotations(
        self,
        name: str,
        annotations: Dict[str, Optional[str]],
        resource_version: Optional[str] = None,
    ) -> dict:
        """Merge-patch metadata.annotations; None deletes (ref:
        PatchNodeAnnotations util.go:262-284).  When ``resource_version`` is
        given the patch is conditional and raises Conflict on mismatch —
        the optimistic-concurrency semantics of client-go's Update() that the
        reference's node lock relies on (nodelock.go:60-61)."""
        with self._lock:
            if name not in self._nodes:
                raise NotFound(f"node {name}")
            node = self._nodes[name]
            if (
                resource_version is not None
                and node["metadata"].get("resourceVersion") != resource_version
            ):
                raise Conflict(f"node {name}: resourceVersion mismatch")
            annos = node["metadata"].setdefault("annotations", {})
            for k, v in annotations.items():
                if v is None:
                    annos.pop(k, None)
                else:
                    annos[k] = v
            self._bump(node)
            self._notify("Node", node)
            return copy.deepcopy(node)

    # -- coordination.k8s.io/v1 Lease objects -----------------------------
    # The kube-native leader-election primitive (the object client-go's
    # leaderelection package CASes on).  Update() is ALWAYS
    # resourceVersion-conditional — apiserver PUT semantics: a stale rv
    # in the submitted object is 409 Conflict — which is exactly the
    # optimistic-concurrency the annotation-lease elector relied on.

    def get_lease(self, name: str, namespace: str = "vtpu-system") -> dict:
        with self._lock:
            k = self._key(namespace, name)
            if k not in self._leases:
                raise NotFound(f"lease {k}")
            return copy.deepcopy(self._leases[k])

    def create_lease(self, lease: dict) -> dict:
        with self._lock:
            md = lease["metadata"]
            k = self._key(md.get("namespace", "vtpu-system"), md["name"])
            if k in self._leases:
                # apiserver semantics: create of an existing object is
                # 409 AlreadyExists — the loser of a creation race must
                # become a follower, never silently overwrite the winner
                raise Conflict(f"lease {k} already exists")
            self._bump(lease)
            self._leases[k] = copy.deepcopy(lease)
            self._notify("Lease", lease)
            return copy.deepcopy(lease)

    def update_lease(
        self, name: str, lease: dict, namespace: str = "vtpu-system"
    ) -> dict:
        with self._lock:
            k = self._key(namespace, name)
            if k not in self._leases:
                raise NotFound(f"lease {k}")
            current = self._leases[k]
            sent_rv = lease.get("metadata", {}).get("resourceVersion")
            if sent_rv != current["metadata"].get("resourceVersion"):
                raise Conflict(f"lease {k}: resourceVersion mismatch")
            fresh = copy.deepcopy(lease)
            fresh["metadata"]["name"] = name
            fresh["metadata"]["namespace"] = namespace
            self._bump(fresh)
            self._leases[k] = copy.deepcopy(fresh)
            self._notify("Lease", fresh)
            return copy.deepcopy(fresh)

    # -- pods -------------------------------------------------------------
    def create_pod(self, pod: dict) -> dict:
        with self._lock:
            k = self._key(pod["metadata"].get("namespace", "default"), pod["metadata"]["name"])
            self._bump(pod)
            self._pods[k] = copy.deepcopy(pod)
            self._notify("Pod", pod)
            return copy.deepcopy(pod)

    def get_pod(self, namespace: str, name: str) -> dict:
        with self._lock:
            k = self._key(namespace, name)
            if k not in self._pods:
                raise NotFound(f"pod {k}")
            return copy.deepcopy(self._pods[k])

    def list_pods(self, node_name: Optional[str] = None) -> List[dict]:
        """List pods, optionally filtered by spec.nodeName (the field selector
        the device plugin uses to find its pending pod, ref util.go:55-80)."""
        with self._lock:
            pods = [copy.deepcopy(p) for p in self._pods.values()]
        if node_name is not None:
            pods = [p for p in pods if p.get("spec", {}).get("nodeName") == node_name]
        return pods

    def patch_pod_annotations(
        self, namespace: str, name: str, annotations: Dict[str, Optional[str]]
    ) -> dict:
        with self._lock:
            k = self._key(namespace, name)
            if k not in self._pods:
                raise NotFound(f"pod {k}")
            pod = self._pods[k]
            annos = pod["metadata"].setdefault("annotations", {})
            for key, v in annotations.items():
                if v is None:
                    annos.pop(key, None)
                else:
                    annos[key] = v
            self._bump(pod)
            self._notify("Pod", pod)
            return copy.deepcopy(pod)

    def bind_pod(self, namespace: str, name: str, node_name: str) -> None:
        """POST pods/<name>/binding analog (ref: scheduler.go:428)."""
        with self._lock:
            k = self._key(namespace, name)
            if k not in self._pods:
                raise NotFound(f"pod {k}")
            pod = self._pods[k]
            pod.setdefault("spec", {})["nodeName"] = node_name
            self._bump(pod)
            self._notify("Pod", pod)

    def delete_pod(self, namespace: str, name: str) -> None:
        with self._lock:
            k = self._key(namespace, name)
            pod = self._pods.pop(k, None)
            if pod is not None:
                self._notify("PodDeleted", pod)
