"""Shared client exceptions (real and fake clients raise the same types)."""


class NotFound(Exception):
    pass


class Conflict(Exception):
    """Optimistic-concurrency conflict (resourceVersion mismatch)."""
