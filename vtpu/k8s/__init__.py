"""Minimal Kubernetes client layer (ref: pkg/k8sutil, client-go usage).

Objects are plain dicts shaped exactly like the Kubernetes JSON API — the
same property that makes the reference's annotation bus inspectable with
kubectl keeps this layer thin and testable.  `FakeClient` is the in-memory
analog of client-go's fake.NewSimpleClientset (SURVEY.md §4: "a fake clientset
can simulate the whole register→filter→bind→allocate handshake in-process").
"""

from vtpu.k8s.fake import FakeClient  # noqa: F401
from vtpu.k8s.objects import (  # noqa: F401
    get_annotations,
    new_node,
    new_pod,
    pod_uid,
)
