"""Helpers over dict-shaped Kubernetes objects."""

from __future__ import annotations

import uuid as _uuid
from typing import Dict, List, Optional


def new_node(name: str, annotations: Optional[Dict[str, str]] = None) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {"name": name, "annotations": dict(annotations or {})},
        "status": {},
    }


def new_pod(
    name: str,
    namespace: str = "default",
    containers: Optional[List[dict]] = None,
    annotations: Optional[Dict[str, str]] = None,
    uid: Optional[str] = None,
    node_name: Optional[str] = None,
) -> dict:
    """Build a minimal pod object.  Each container:
    ``{"name": ..., "resources": {"limits": {...}, "requests": {...}}}``.
    """
    pod = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "uid": uid or str(_uuid.uuid4()),
            "annotations": dict(annotations or {}),
            "labels": {},
        },
        "spec": {"containers": list(containers or [])},
        "status": {"phase": "Pending"},
    }
    if node_name:
        pod["spec"]["nodeName"] = node_name
    return pod


def get_annotations(obj: dict) -> Dict[str, str]:
    return obj.setdefault("metadata", {}).setdefault("annotations", {})


def pod_uid(pod: dict) -> str:
    return pod["metadata"]["uid"]


def pod_key(pod: dict) -> str:
    return f"{pod['metadata'].get('namespace', 'default')}/{pod['metadata']['name']}"


def container_limits(container: dict) -> Dict[str, str]:
    res = container.get("resources") or {}
    limits = dict(res.get("limits") or {})
    # limits→requests fallback (ref: pkg/k8sutil/pod.go:27-119 uses limits,
    # falling back to requests when a limit is absent)
    for k, v in (res.get("requests") or {}).items():
        limits.setdefault(k, v)
    return limits
