{{/*
Shared fragments for the per-family device-plugin daemonsets
(ref charts/vgpu: one daemonset per vendor, same image/sidecar shape).
Keeping the postStart shim copy and the monitor sidecar in one place
stops the two families' daemonsets drifting apart.
*/}}

{{- define "vtpu.shimCopyCommand" -}}
["/bin/sh", "-c", "mkdir -p {{ .Values.devicePlugin.shimHostDir }} && cp -f /app/cpp/build/libvtpu_shim.so /app/shim/ld.so.preload /app/cpp/build/vtpu-prestart {{ .Values.devicePlugin.shimHostDir }}/ 2>/dev/null || true"]
{{- end }}

{{- define "vtpu.monitorContainer" -}}
- name: monitor
  image: "{{ .Values.image.repository }}:{{ .Values.image.tag }}"
  imagePullPolicy: {{ .Values.image.pullPolicy }}
  command:
    - python3
    - /app/cmd/vtpu_monitor.py
    - --containers-root={{ .Values.devicePlugin.cacheHostRoot }}
    - --metrics-bind=0.0.0.0:{{ .Values.monitor.metricsPort }}
    - --noderpc-bind=0.0.0.0:{{ .Values.monitor.noderpcPort }}
    - --feedback-interval={{ .Values.monitor.feedbackInterval }}
  env:
    - name: NODE_NAME
      valueFrom: {fieldRef: {fieldPath: spec.nodeName}}
  ports:
    - {containerPort: {{ .Values.monitor.metricsPort }}, name: metrics}
  volumeMounts:
    - {name: vtpu-host, mountPath: /usr/local/vtpu}
{{- end }}
