{{/*
Shared fragments for the per-family device-plugin daemonsets
(ref charts/vgpu: one daemonset per vendor, same image/sidecar shape).
Keeping the postStart shim copy and the monitor sidecar in one place
stops the two families' daemonsets drifting apart.
*/}}

{{- define "vtpu.shimCopyCommand" -}}
["/bin/sh", "-c", "mkdir -p {{ .Values.devicePlugin.shimHostDir }} && cp -f /app/cpp/build/libvtpu_shim.so /app/shim/ld.so.preload /app/cpp/build/vtpu-prestart {{ .Values.devicePlugin.shimHostDir }}/ 2>/dev/null || true"]
{{- end }}

{{- define "vtpu.monitorContainer" -}}
- name: monitor
  image: "{{ .Values.image.repository }}:{{ .Values.image.tag }}"
  imagePullPolicy: {{ .Values.image.pullPolicy }}
  command:
    - python3
    - /app/cmd/vtpu_monitor.py
    - --containers-root={{ .Values.devicePlugin.cacheHostRoot }}
    - --metrics-bind=0.0.0.0:{{ .Values.monitor.metricsPort }}
    - --noderpc-bind=0.0.0.0:{{ .Values.monitor.noderpcPort }}
    - --feedback-interval={{ .Values.monitor.feedbackInterval }}
  env:
    - name: NODE_NAME
      valueFrom: {fieldRef: {fieldPath: spec.nodeName}}
  ports:
    - {containerPort: {{ .Values.monitor.metricsPort }}, name: metrics}
  volumeMounts:
    - {name: vtpu-host, mountPath: /usr/local/vtpu}
{{- end }}

{{/*
Resource-name prefix: .Release.Name by default (stable rendered names),
nameOverride appends, fullnameOverride replaces outright (the operator
knob surface of ref charts/vgpu/values.yaml:1-20, vtpu naming kept).
*/}}
{{- define "vtpu.fullname" -}}
{{- if .Values.fullnameOverride -}}
{{- .Values.fullnameOverride | trunc 63 | trimSuffix "-" -}}
{{- else if .Values.nameOverride -}}
{{- printf "%s-%s" .Release.Name .Values.nameOverride | trunc 63 | trimSuffix "-" -}}
{{- else -}}
{{- .Release.Name -}}
{{- end -}}
{{- end }}

{{/* cluster-wide operator labels/annotations, merged into workloads */}}
{{- define "vtpu.globalLabels" -}}
{{- with .Values.global.labels }}
{{ toYaml . }}
{{- end }}
{{- end }}

{{- define "vtpu.globalAnnotations" -}}
{{- with .Values.global.annotations }}
{{ toYaml . }}
{{- end }}
{{- end }}

{{- define "vtpu.imagePullSecrets" -}}
{{- with .Values.imagePullSecrets }}
imagePullSecrets:
{{ toYaml . }}
{{- end }}
{{- end }}
